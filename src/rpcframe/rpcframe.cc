// Native RPC wire framer: the per-byte hot path of rpc.py's msgpack
// framing moved into C (see docs/data_plane.md "Native framer").
//
// Three pieces, all driven from Python over ctypes (no CPython API, same
// build discipline as ../object_store/store.cc):
//
//   1. rf_scan: a streaming msgpack BOUNDARY scanner.  It never builds
//      objects — it skip-parses type headers to find frame boundaries,
//      detects the raw out-of-band header frame
//      [0, "__raw__", [rid, nbytes]] at frame starts (magic-prefix
//      compare + two-int parse), and splits an arbitrary stream chunk
//      into ordered events: CONTROL spans (fed to the Python-side
//      msgpack decoder), RAW_BEGIN markers, and RAW payload spans
//      (scattered straight into their destination buffer).  This
//      replaces the Python path's "reset the Unpacker and re-feed the
//      leftover" dance on every raw header, and is what lets the
//      receive loop hand payload bytes to the shm arena without an
//      intermediate pass.  State survives arbitrary chunk boundaries —
//      including a raw header split anywhere, which lands in a small
//      stash until it can be classified.
//
//   2. rf_writev: gather-write a whole frame wave (or a raw header +
//      arena payload views) in ONE writev syscall, looping on partial
//      writes and stopping cleanly at EAGAIN so the caller can hand the
//      unsent tail back to the transport's backpressure machinery.
//
//   3. rf_recv_into: drain the socket DIRECTLY into a destination
//      buffer (the shm arena region of an in-flight pull), looping
//      until the payload completes or the socket would block — the
//      readinto-scatter that removes the per-read bytes allocation and
//      Python slice-assign from bulk pulls.
//
// Error reporting: negative errno by convention where a single return
// value suffices; out-params otherwise.  No globals, no locks — one
// scanner per connection, owned by the caller.

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <new>
#include <sys/socket.h>
#include <sys/types.h>
#include <sys/uio.h>
#include <unistd.h>

namespace {

constexpr int kIovBatch = 64;        // iovecs per writev call
constexpr uint32_t kStashCap = 48;   // >= max raw header (29 bytes)
constexpr uint32_t kSpillCap = 256;  // mismatch-resolved stash bytes/scan
constexpr uint32_t kHdrCap = 8;      // >= 1 type byte + 4 length bytes

// Event types surfaced to Python (keep in sync with rpcframe.py).
enum {
  EV_CTRL = 0,        // a = chunk offset, b = length: feed the decoder
  EV_RAW_BEGIN = 1,   // a = rid, b = nbytes
  EV_RAW_DATA = 2,    // a = chunk offset, b = length: scatter to sink
  EV_STASH_CTRL = 3,  // a = spill offset, b = length: feed the decoder
};

struct Scanner {
  // Control-stream skip-parse state.
  uint64_t items;      // msgpack objects still needed to close the frame
  uint64_t skip;       // payload bytes of the current str/bin/scalar left
  uint8_t hdr[kHdrCap];  // split multi-byte type header
  uint32_t hdr_have, hdr_need;
  // Cross-chunk raw-header candidate (frame-start bytes matching the
  // magic prefix so far).
  uint8_t stash[kStashCap];
  uint32_t stash_len;
  // Raw payload mode.
  uint64_t raw_remaining;
  // Stash bytes reclassified as control this scan call (referenced by
  // EV_STASH_CTRL events; reset at every rf_scan entry).
  uint8_t spill[kSpillCap];
  uint32_t spill_len;
  int error;
};

// packb([0, "__raw__", ...]) prefix: fixarray(3), fixint 0, fixstr(7).
// WIRE INVARIANT: raw headers are recognized by this byte-exact
// MINIMAL msgpack encoding (what msgpack-python's packb emits).  A
// packer choosing a legal-but-non-minimal form (str8 name, uint8 0)
// would slip past this magic; the Python dispatch layer aborts such a
// connection typed (rpc._on_msg "__raw__" guard) rather than letting
// the payload bytes desync the frame parser.  Any future non-Python
// peer must pack raw headers minimally.
const uint8_t kMagic[10] = {0x93, 0x00, 0xa7, '_', '_', 'r', 'a', 'w',
                            '_', '_'};

// Extra header bytes following a msgpack type byte; -1 = invalid.
static int extra_of(uint8_t b) {
  switch (b) {
    case 0xc1: return -1;
    case 0xc4: case 0xd9: return 1;                   // bin8 / str8
    case 0xc5: case 0xda: return 2;                   // bin16 / str16
    case 0xc6: case 0xdb: return 4;                   // bin32 / str32
    case 0xc7: return 1;                              // ext8 (len; type
    case 0xc8: return 2;                              //  byte counts as
    case 0xc9: return 4;                              //  payload below)
    case 0xdc: case 0xde: return 2;                   // array16 / map16
    case 0xdd: case 0xdf: return 4;                   // array32 / map32
    default: return 0;
  }
}

static uint64_t be_read(const uint8_t* p, int n) {
  uint64_t v = 0;
  for (int i = 0; i < n; i++) v = (v << 8) | p[i];
  return v;
}

// Decode one complete type header (type byte at h[0], `extra` length
// bytes after): how many payload bytes to skip and how many child
// objects it opens.
static void decode_hdr(const uint8_t* h, int extra, uint64_t* payload,
                       uint64_t* children) {
  uint8_t b = h[0];
  *payload = 0;
  *children = 0;
  if (b <= 0x7f || b >= 0xe0 || b == 0xc0 || b == 0xc2 || b == 0xc3)
    return;                                           // scalar
  if (b >= 0x80 && b <= 0x8f) { *children = 2ull * (b & 0x0f); return; }
  if (b >= 0x90 && b <= 0x9f) { *children = b & 0x0f; return; }
  if (b >= 0xa0 && b <= 0xbf) { *payload = b & 0x1f; return; }
  switch (b) {
    case 0xc4: case 0xc5: case 0xc6:                  // bin
    case 0xd9: case 0xda: case 0xdb:                  // str
      *payload = be_read(h + 1, extra); return;
    case 0xc7: case 0xc8: case 0xc9:                  // ext: +1 type byte
      *payload = be_read(h + 1, extra) + 1; return;
    case 0xca: *payload = 4; return;                  // float32
    case 0xcb: *payload = 8; return;                  // float64
    case 0xcc: *payload = 1; return;
    case 0xcd: *payload = 2; return;
    case 0xce: *payload = 4; return;
    case 0xcf: *payload = 8; return;
    case 0xd0: *payload = 1; return;
    case 0xd1: *payload = 2; return;
    case 0xd2: *payload = 4; return;
    case 0xd3: *payload = 8; return;
    case 0xd4: *payload = 2; return;                  // fixext1
    case 0xd5: *payload = 3; return;
    case 0xd6: *payload = 5; return;
    case 0xd7: *payload = 9; return;
    case 0xd8: *payload = 17; return;                 // fixext16
    case 0xdc: case 0xdd: *children = be_read(h + 1, extra); return;
    case 0xde: case 0xdf: *children = 2ull * be_read(h + 1, extra); return;
  }
}

// Skip-parse control bytes.  Consumes until the buffer is exhausted, the
// current FRAME completes (*boundary = 1), or a malformed byte sets
// sc->error.  Returns bytes consumed.
static uint64_t ctrl_parse(Scanner* sc, const uint8_t* p, uint64_t len,
                           int* boundary) {
  uint64_t pos = 0;
  *boundary = 0;
  while (pos < len) {
    if (sc->skip) {
      uint64_t t = sc->skip < len - pos ? sc->skip : len - pos;
      sc->skip -= t;
      pos += t;
      if (sc->skip) return pos;
      if (sc->items == 0) { *boundary = 1; return pos; }
      continue;
    }
    if (sc->hdr_need) {                     // finish a split type header
      while (sc->hdr_have < sc->hdr_need && pos < len)
        sc->hdr[sc->hdr_have++] = p[pos++];
      if (sc->hdr_have < sc->hdr_need) return pos;
      uint64_t payload, children;
      decode_hdr(sc->hdr, (int)(sc->hdr_need - 1), &payload, &children);
      sc->hdr_have = sc->hdr_need = 0;
      if (sc->items == 0) sc->items = 1;    // root object of a new frame
      sc->items -= 1;
      sc->items += children;
      sc->skip = payload;
      if (sc->skip == 0 && sc->items == 0) { *boundary = 1; return pos; }
      continue;
    }
    uint8_t b = p[pos];
    int extra = extra_of(b);
    if (extra < 0) { sc->error = 1; return pos; }
    if (len - pos < (uint64_t)(1 + extra)) {
      sc->hdr_need = 1 + (uint32_t)extra;
      sc->hdr_have = 0;
      while (pos < len) sc->hdr[sc->hdr_have++] = p[pos++];
      return pos;
    }
    uint64_t payload, children;
    decode_hdr(p + pos, extra, &payload, &children);
    pos += 1 + (uint64_t)extra;
    if (sc->items == 0) sc->items = 1;
    sc->items -= 1;
    sc->items += children;
    sc->skip = payload;
    if (sc->skip == 0 && sc->items == 0) { *boundary = 1; return pos; }
  }
  return pos;
}

// Parse one msgpack int at p.  Returns bytes consumed (>0), 0 = need
// more input, -1 = not an int.
static int parse_int(const uint8_t* p, uint64_t avail, int64_t* out) {
  if (avail == 0) return 0;
  uint8_t b = p[0];
  if (b <= 0x7f) { *out = b; return 1; }
  if (b >= 0xe0) { *out = (int8_t)b; return 1; }
  int n;
  bool sign;
  switch (b) {
    case 0xcc: n = 1; sign = false; break;
    case 0xcd: n = 2; sign = false; break;
    case 0xce: n = 4; sign = false; break;
    case 0xcf: n = 8; sign = false; break;
    case 0xd0: n = 1; sign = true; break;
    case 0xd1: n = 2; sign = true; break;
    case 0xd2: n = 4; sign = true; break;
    case 0xd3: n = 8; sign = true; break;
    default: return -1;
  }
  if (avail < (uint64_t)(1 + n)) return 0;
  uint64_t v = be_read(p + 1, n);
  if (sign) {
    // Sign-extend from n bytes.
    if (n < 8 && (v & (1ull << (8 * n - 1))))
      v |= ~((1ull << (8 * n)) - 1);
    *out = (int64_t)v;
  } else {
    *out = (int64_t)v;
  }
  return 1 + n;
}

enum { PROBE_NO_MATCH = 0, PROBE_NEED_MORE = 1, PROBE_MATCH = 2,
       PROBE_ERROR = 3 };

// Classify the bytes at a frame start: a complete raw header
// [0,"__raw__",[rid,nbytes]] (MATCH: *consumed/*rid/*nbytes set), a
// possible prefix of one (NEED_MORE), ordinary control bytes
// (NO_MATCH — only before the 10-byte magic fully matches), or a
// CLAIMED-but-malformed raw header (ERROR).  Once the magic matches,
// the frame is a raw header or nothing: the pure-Python framer aborts
// the connection on a bad [rid, nbytes] shape, and reclassifying it as
// control here would instead desync — the following payload bytes
// would parse as frames (parity requirement; see _ingest's typed
// "bad raw frame length" RpcError).
static int probe_raw_header(const uint8_t* p, uint64_t avail,
                            uint64_t* consumed, int64_t* rid,
                            int64_t* nbytes) {
  uint64_t n = avail < 10 ? avail : 10;
  if (memcmp(p, kMagic, n) != 0) return PROBE_NO_MATCH;
  if (avail < 11) return PROBE_NEED_MORE;
  if (p[10] != 0x92) return PROBE_ERROR;      // third elem not [rid, n]
  uint64_t pos = 11;
  int r = parse_int(p + pos, avail - pos, rid);
  if (r == 0) return PROBE_NEED_MORE;
  if (r < 0) return PROBE_ERROR;
  pos += (uint64_t)r;
  r = parse_int(p + pos, avail - pos, nbytes);
  if (r == 0) return PROBE_NEED_MORE;
  if (r < 0 || *nbytes < 0) return PROBE_ERROR;
  *consumed = pos + (uint64_t)r;
  return PROBE_MATCH;
}

}  // namespace

extern "C" {

int rf_abi_version() { return 1; }

void* rf_scanner_new() {
  Scanner* sc = new (std::nothrow) Scanner();
  if (sc) memset(sc, 0, sizeof(*sc));
  return sc;
}

void rf_scanner_free(void* h) { delete static_cast<Scanner*>(h); }

void rf_scanner_reset(void* h) {
  Scanner* sc = static_cast<Scanner*>(h);
  memset(sc, 0, sizeof(*sc));
}

// Resynchronize the scanner's raw-payload countdown after the caller
// consumed payload bytes OUTSIDE the scanner (native recv takeover).
void rf_scanner_set_raw_remaining(void* h, uint64_t remaining) {
  static_cast<Scanner*>(h)->raw_remaining = remaining;
}

uint64_t rf_scanner_raw_remaining(void* h) {
  return static_cast<Scanner*>(h)->raw_remaining;
}

const uint8_t* rf_scanner_spill_ptr(void* h) {
  return static_cast<Scanner*>(h)->spill;
}

// Scan one stream chunk into events.  Returns the number of events
// (>= 0) or -1 on a malformed stream (caller drops the connection).
// *consumed_out reports how many input bytes the events cover; when the
// event arrays fill up it may be < len and the caller re-feeds the
// remainder (event offsets are relative to the fed pointer).
int64_t rf_scan(void* h, const uint8_t* data, uint64_t len,
                int32_t* ev_type, int64_t* ev_a, int64_t* ev_b,
                int32_t max_events, uint64_t* consumed_out) {
  Scanner* sc = static_cast<Scanner*>(h);
  sc->spill_len = 0;
  int32_t nev = 0;
  uint64_t pos = 0;
  int64_t ctrl_open = -1;  // start offset of the open CTRL span

#define EMIT(t, a, b)                                               \
  do {                                                              \
    ev_type[nev] = (t);                                             \
    ev_a[nev] = (int64_t)(a);                                       \
    ev_b[nev] = (int64_t)(b);                                       \
    nev++;                                                          \
  } while (0)
#define CLOSE_CTRL()                                                \
  do {                                                              \
    if (ctrl_open >= 0) {                                           \
      EMIT(EV_CTRL, ctrl_open, (int64_t)pos - ctrl_open);           \
      ctrl_open = -1;                                               \
    }                                                               \
  } while (0)

  while (pos < len) {
    if (nev >= max_events - 4) break;  // room for CLOSE_CTRL + 3 more

    if (sc->raw_remaining) {
      CLOSE_CTRL();
      uint64_t take = sc->raw_remaining < len - pos ? sc->raw_remaining
                                                    : len - pos;
      EMIT(EV_RAW_DATA, pos, take);
      sc->raw_remaining -= take;
      pos += take;
      continue;
    }

    if (sc->stash_len) {
      // Cross-chunk raw-header candidate: append bytes until it can be
      // classified.
      CLOSE_CTRL();
      for (;;) {
        uint64_t consumed = 0;
        int64_t rid, nbytes;
        int r = probe_raw_header(sc->stash, sc->stash_len, &consumed,
                                 &rid, &nbytes);
        if (r == PROBE_MATCH) {
          // The stash IS exactly the header (built byte-by-byte).
          EMIT(EV_RAW_BEGIN, rid, nbytes);
          sc->raw_remaining = (uint64_t)nbytes;
          sc->stash_len = 0;
          break;
        }
        if (r == PROBE_NEED_MORE) {
          if (pos >= len) { *consumed_out = pos; return nev; }
          if (sc->stash_len >= kStashCap) { sc->error = 1; break; }
          sc->stash[sc->stash_len++] = data[pos++];
          continue;
        }
        if (r == PROBE_ERROR) { sc->error = 1; break; }
        // Mismatch: the stashed bytes are ordinary control stream.
        // Skip-parse them to keep frame accounting, then hand them to
        // the decoder via the spill buffer.  A frame can complete
        // inside the stash; a remainder that starts a new candidate
        // loops back into the probe above.
        uint32_t off = 0;
        while (off < sc->stash_len) {
          if (sc->items == 0 && sc->skip == 0 && sc->hdr_need == 0 &&
              sc->stash[off] == 0x93 && off > 0)
            break;  // new frame-start candidate inside the stash
          int boundary = 0;
          uint64_t c = ctrl_parse(sc, sc->stash + off,
                                  sc->stash_len - off, &boundary);
          if (sc->error) break;
          off += (uint32_t)c;
          if (!boundary && c == 0) break;  // defensive: no progress
        }
        if (sc->error) break;
        if (off > 0) {
          if (sc->spill_len + off > kSpillCap) { sc->error = 1; break; }
          memcpy(sc->spill + sc->spill_len, sc->stash, off);
          EMIT(EV_STASH_CTRL, sc->spill_len, off);
          sc->spill_len += off;
          memmove(sc->stash, sc->stash + off, sc->stash_len - off);
          sc->stash_len -= off;
        }
        if (sc->stash_len == 0) break;
        // Remainder starts with 0x93 at a frame boundary: re-probe.
      }
      if (sc->error) { *consumed_out = pos; return -1; }
      continue;
    }

    if (sc->items == 0 && sc->skip == 0 && sc->hdr_need == 0 &&
        data[pos] == 0x93) {
      // Frame start that might be a raw header.
      uint64_t consumed = 0;
      int64_t rid, nbytes;
      int r = probe_raw_header(data + pos, len - pos, &consumed, &rid,
                               &nbytes);
      if (r == PROBE_MATCH) {
        CLOSE_CTRL();
        EMIT(EV_RAW_BEGIN, rid, nbytes);
        sc->raw_remaining = (uint64_t)nbytes;
        pos += consumed;
        continue;
      }
      if (r == PROBE_NEED_MORE) {
        // Chunk ends inside a possible header: stash the tail.
        CLOSE_CTRL();
        uint64_t tail = len - pos;
        if (tail > kStashCap) { *consumed_out = pos; return -1; }
        memcpy(sc->stash, data + pos, tail);
        sc->stash_len = (uint32_t)tail;
        pos = len;
        break;
      }
      if (r == PROBE_ERROR) { *consumed_out = pos; return -1; }
      // NO_MATCH falls through: parse as ordinary control bytes.
    }

    if (ctrl_open < 0) ctrl_open = (int64_t)pos;
    int boundary = 0;
    uint64_t c = ctrl_parse(sc, data + pos, len - pos, &boundary);
    if (sc->error) { *consumed_out = pos + c; return -1; }
    pos += c;
    // On boundary the outer loop re-checks for a raw header; the CTRL
    // span stays open across ordinary frame boundaries.
  }
  CLOSE_CTRL();
  *consumed_out = pos;
  return nev;
#undef EMIT
#undef CLOSE_CTRL
}

// Gather-write `n` buffers starting at logical offset `skip` (bytes of
// the wave already written by an earlier call).  Loops writev until the
// wave completes or the socket would block.  Returns total bytes written
// THIS call; *err_out = 0 on success/EAGAIN, errno on a hard error;
// *nsys_out = syscalls issued.
int64_t rf_writev(int fd, void* const* bufs, const uint64_t* lens,
                  int32_t n, uint64_t skip, int32_t* err_out,
                  int32_t* nsys_out) {
  *err_out = 0;
  *nsys_out = 0;
  int64_t total = 0;
  int32_t idx = 0;
  uint64_t off = 0;
  while (idx < n && skip) {  // resume position
    if (skip >= lens[idx]) { skip -= lens[idx]; idx++; }
    else { off = skip; skip = 0; }
  }
  while (idx < n) {
    struct iovec iov[kIovBatch];
    int cnt = 0;
    int32_t i = idx;
    uint64_t o = off;
    for (; i < n && cnt < kIovBatch; i++) {
      uint64_t l = lens[i] - o;
      if (l) {
        iov[cnt].iov_base = (char*)bufs[i] + o;
        iov[cnt].iov_len = l;
        cnt++;
      }
      o = 0;
    }
    if (cnt == 0) break;
    ssize_t w = writev(fd, iov, cnt);
    (*nsys_out)++;
    if (w < 0) {
      if (errno == EINTR) continue;
      if (errno != EAGAIN && errno != EWOULDBLOCK) *err_out = errno;
      return total;
    }
    total += w;
    uint64_t adv = (uint64_t)w;
    while (idx < n && adv) {
      uint64_t l = lens[idx] - off;
      if (adv >= l) { adv -= l; idx++; off = 0; }
      else { off += adv; adv = 0; }
    }
  }
  return total;
}

// Drain the socket into buf, looping until `cap` bytes arrive or the
// socket would block.  *state_out: 0 = would block (come back on the
// next readable event), 1 = EOF, 2 = hard error (errno in *err_out),
// 3 = cap filled.  Returns bytes read this call.
int64_t rf_recv_into(int fd, void* buf, uint64_t cap, int32_t* state_out,
                     int32_t* err_out, int32_t* nsys_out) {
  *err_out = 0;
  *nsys_out = 0;
  int64_t got = 0;
  while ((uint64_t)got < cap) {
    ssize_t r = recv(fd, (char*)buf + got, cap - got, 0);
    (*nsys_out)++;
    if (r < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) { *state_out = 0; return got; }
      *state_out = 2;
      *err_out = errno;
      return got;
    }
    if (r == 0) { *state_out = 1; return got; }
    got += r;
  }
  *state_out = 3;
  return got;
}

}  // extern "C"
